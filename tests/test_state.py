"""M1 state-plane tests: store CAS/watch semantics, client subresources,
informer sync + handlers, workqueue dedup/backoff.

Modeled on the reference's storage/cacher and tools/cache unit tests."""

import threading
import time

import pytest

from kubernetes_tpu import api
from kubernetes_tpu.state import (ADDED, AlreadyExistsError, Client,
                                  ConflictError, DELETED, EventHandlers,
                                  ExpiredError, MODIFIED, NotFoundError,
                                  RateLimitingQueue, SharedInformerFactory,
                                  Store, WorkQueue)


def make_pod(name, ns="default", node=""):
    return api.Pod(metadata=api.ObjectMeta(name=name, namespace=ns),
                   spec=api.PodSpec(node_name=node,
                                    containers=[api.Container(name="c", image="i")]))


class TestStore:
    def test_create_get_rv(self):
        s = Store()
        created = s.create("pods", make_pod("a"))
        assert created.metadata.resource_version == "1"
        assert created.metadata.uid
        got = s.get("pods", "default", "a")
        assert got.metadata.name == "a"

    def test_create_duplicate(self):
        s = Store()
        s.create("pods", make_pod("a"))
        with pytest.raises(AlreadyExistsError):
            s.create("pods", make_pod("a"))

    def test_update_cas(self):
        # store objects are read-only (client-go contract); mutate copies
        from kubernetes_tpu.api import serde
        s = Store()
        s.create("pods", make_pod("a"))
        p1 = serde.deepcopy_obj(s.get("pods", "default", "a"))
        p2 = serde.deepcopy_obj(s.get("pods", "default", "a"))
        p1.spec.node_name = "n1"
        s.update("pods", p1)
        p2.spec.node_name = "n2"
        with pytest.raises(ConflictError):
            s.update("pods", p2)  # stale rv

    def test_guaranteed_update_retries(self):
        s = Store()
        s.create("pods", make_pod("a"))
        def mutate(pod):
            pod.metadata.labels["x"] = "y"
            return pod
        out = s.guaranteed_update("pods", "default", "a", mutate)
        assert out.metadata.labels["x"] == "y"

    def test_delete_not_found(self):
        s = Store()
        with pytest.raises(NotFoundError):
            s.delete("pods", "default", "missing")

    def test_finalizers_block_deletion(self):
        s = Store()
        pod = make_pod("a")
        pod.metadata.finalizers = ["example/finalizer"]
        s.create("pods", pod)
        marked = s.delete("pods", "default", "a")
        assert marked.metadata.deletion_timestamp is not None
        # still gettable until finalizer removed
        assert s.get("pods", "default", "a").metadata.name == "a"
        # a tombstoned key cannot be re-created (409 until finalization)
        with pytest.raises(AlreadyExistsError):
            s.create("pods", make_pod("a"))
        # removing the last finalizer completes the deletion (mutate a copy:
        # get() returns the canonical read-only object)
        from kubernetes_tpu.api import serde
        w = s.watch("pods")
        cur = serde.deepcopy_obj(s.get("pods", "default", "a"))
        cur.metadata.finalizers = []
        s.update("pods", cur)
        ev = w.events.get(timeout=1)
        assert ev.type == DELETED
        with pytest.raises(NotFoundError):
            s.get("pods", "default", "a")

    def test_watch_from_now(self):
        s = Store()
        w = s.watch("pods")
        s.create("pods", make_pod("a"))
        ev = w.events.get(timeout=1)
        assert ev.type == ADDED and ev.object.metadata.name == "a"
        s.delete("pods", "default", "a")
        ev = w.events.get(timeout=1)
        assert ev.type == DELETED

    def test_watch_resume_from_rv(self):
        s = Store()
        s.create("pods", make_pod("a"))
        items, rv = s.list("pods")
        s.create("pods", make_pod("b"))
        s.create("pods", make_pod("c"))
        w = s.watch("pods", resource_version=rv)
        names = [w.events.get(timeout=1).object.metadata.name for _ in range(2)]
        assert names == ["b", "c"]

    def test_watch_namespace_filter(self):
        s = Store()
        w = s.watch("pods", namespace="prod")
        s.create("pods", make_pod("a", ns="dev"))
        s.create("pods", make_pod("b", ns="prod"))
        ev = w.events.get(timeout=1)
        assert ev.object.metadata.namespace == "prod"

    def test_watch_expired(self):
        s = Store()
        s.HISTORY_WINDOW = 4
        for i in range(10):
            s.create("pods", make_pod(f"p{i}"))
        with pytest.raises(ExpiredError):
            s.watch("pods", resource_version=1)

    def test_stored_objects_isolated(self):
        s = Store()
        pod = make_pod("a")
        s.create("pods", pod)
        pod.spec.node_name = "mutated-after-create"
        assert s.get("pods", "default", "a").spec.node_name == ""


class TestClient:
    def test_create_defaults_and_validates(self):
        c = Client()
        pod = c.pods().create(make_pod("a"))
        assert pod.spec.scheduler_name == "default-scheduler"
        with pytest.raises(api.ValidationError):
            c.pods().create(api.Pod(metadata=api.ObjectMeta(name="bad")))

    def test_bind_subresource(self):
        c = Client()
        c.pods().create(make_pod("a"))
        binding = api.Binding(metadata=api.ObjectMeta(name="a", namespace="default"),
                              target=api.ObjectReference(kind="Node", name="n1"))
        bound = c.pods().bind(binding)
        assert bound.spec.node_name == "n1"
        assert any(cond.type == "PodScheduled" and cond.status == "True"
                   for cond in bound.status.conditions)
        # double-bind to a different node conflicts
        binding2 = api.Binding(metadata=api.ObjectMeta(name="a", namespace="default"),
                               target=api.ObjectReference(kind="Node", name="n2"))
        with pytest.raises(ConflictError):
            c.pods().bind(binding2)

    def test_update_status_does_not_touch_spec(self):
        from kubernetes_tpu.api import serde
        c = Client()
        c.pods().create(make_pod("a"))
        stale = serde.deepcopy_obj(c.pods().get("a"))
        stale.spec.node_name = "sneaky"
        stale.status.phase = "Running"
        c.pods().update_status(stale)
        cur = c.pods().get("a")
        assert cur.spec.node_name == ""
        assert cur.status.phase == "Running"

    def test_label_selector_list(self):
        c = Client()
        p = make_pod("a"); p.metadata.labels = {"app": "web"}
        c.pods().create(p)
        c.pods().create(make_pod("b"))
        sel = api.LabelSelector(match_labels={"app": "web"})
        assert [x.metadata.name for x in c.pods().list(label_selector=sel)] == ["a"]

    def test_cluster_scoped(self):
        c = Client()
        c.nodes().create(api.Node(metadata=api.ObjectMeta(name="n1")))
        assert c.nodes().get("n1").metadata.name == "n1"

    def test_bind_conflict_not_retried(self):
        """A semantic bind conflict must propagate immediately, not be
        retried as a CAS race by guaranteed_update."""
        c = Client()
        c.pods().create(make_pod("a"))
        bind = lambda node: c.pods().bind(api.Binding(
            metadata=api.ObjectMeta(name="a", namespace="default"),
            target=api.ObjectReference(kind="Node", name=node)))
        bind("n1")
        calls = 0
        orig_get = c.store.get
        def counting_get(*a, **kw):
            nonlocal calls
            calls += 1
            return orig_get(*a, **kw)
        c.store.get = counting_get
        with pytest.raises(ConflictError):
            bind("n2")
        assert calls == 1  # no retry loop

    def test_empty_dir_volume_survives_round_trip(self):
        c = Client()
        pod = make_pod("a")
        pod.spec.volumes = [api.Volume(name="scratch", empty_dir={})]
        c.pods().create(pod)
        got = c.pods().get("a")
        assert got.spec.volumes[0].empty_dir == {}  # not dropped to None


class TestInformer:
    def test_sync_and_events(self):
        c = Client()
        c.pods().create(make_pod("pre"))
        factory = SharedInformerFactory(c)
        inf = factory.informer_for(api.Pod)
        adds, updates, deletes = [], [], []
        inf.add_event_handlers(EventHandlers(
            on_add=lambda o: adds.append(o.metadata.name),
            on_update=lambda old, new: updates.append(new.metadata.name),
            on_delete=lambda o: deletes.append(o.metadata.name)))
        factory.start()
        assert factory.wait_for_cache_sync()
        assert adds == ["pre"]
        c.pods().create(make_pod("post"))
        c.pods().patch("post", lambda p: p)
        c.pods().delete("post")
        deadline = time.time() + 5
        while time.time() < deadline and "post" not in deletes:
            time.sleep(0.01)
        assert "post" in adds
        assert "post" in deletes
        factory.stop()

    def test_node_name_index(self):
        c = Client()
        c.pods().create(make_pod("a", node="n1"))
        c.pods().create(make_pod("b", node="n2"))
        factory = SharedInformerFactory(c)
        inf = factory.informer_for(api.Pod)
        factory.start()
        assert factory.wait_for_cache_sync()
        on_n1 = inf.indexer.by_index("nodeName", "n1")
        assert [p.metadata.name for p in on_n1] == ["a"]
        factory.stop()

    def test_late_handler_gets_existing_objects(self):
        c = Client()
        c.pods().create(make_pod("a"))
        factory = SharedInformerFactory(c)
        inf = factory.informer_for(api.Pod)
        factory.start()
        assert factory.wait_for_cache_sync()
        adds = []
        inf.add_event_handlers(EventHandlers(on_add=lambda o: adds.append(o.metadata.name)))
        assert adds == ["a"]
        factory.stop()


class TestWorkQueue:
    def test_dedup(self):
        q = WorkQueue()
        q.add("x"); q.add("x"); q.add("y")
        assert q.len() == 2

    def test_readd_while_processing(self):
        q = WorkQueue()
        q.add("x")
        item, _ = q.get()
        q.add("x")            # re-add while in flight
        assert q.len() == 0   # deferred until done
        q.done(item)
        assert q.len() == 1

    def test_rate_limited_backoff(self):
        q = RateLimitingQueue()
        assert q.rate_limiter.when("k") == q.rate_limiter.base_delay
        assert q.rate_limiter.when("k") == q.rate_limiter.base_delay * 2
        q.forget("k")
        assert q.rate_limiter.when("k") == q.rate_limiter.base_delay

    def test_delayed_delivery(self):
        q = RateLimitingQueue()
        q.add_after("soon", 0.05)
        item, shutdown = q.get(timeout=2)
        assert item == "soon" and not shutdown
        q.shutdown()

    def test_workers_drain(self):
        q = WorkQueue()
        seen = []
        def worker():
            while True:
                item, shutdown = q.get()
                if shutdown:
                    return
                seen.append(item)
                q.done(item)
        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(100):
            q.add(i)
        deadline = time.time() + 5
        while time.time() < deadline and len(seen) < 100:
            time.sleep(0.01)
        q.shutdown()
        for t in threads:
            t.join(timeout=2)
        assert sorted(seen) == list(range(100))
